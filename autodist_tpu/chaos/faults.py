"""Fault catalog: every injectable fault class, its seam, and what the
ft/obs stack is expected to do about it.

The catalog is the chaos subsystem's source of truth (docs/chaos.md
renders its table): each :class:`FaultSpec` names the **seam** the fault
enters through (:mod:`autodist_tpu.chaos.hooks`), the **detection** the
stack must produce (a sentry ``SNT###`` code, a doctor ``DOC###``
verdict, or a typed degradation), and the **recovery** contract the soak
harness (:mod:`autodist_tpu.chaos.harness`) asserts. ``--selftest`` fails
if any catalog class was never injected or detected with a different
code than promised here.

Injector implementations live here too — :func:`make_handlers` builds the
per-seam hook closures a :class:`~autodist_tpu.chaos.schedule.ChaosPlant`
installs. All randomness (which byte to flip, which file to truncate)
comes from the plant's seeded RNG and lands in the injection trace, so a
schedule replay is byte-for-byte reproducible.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Dict, List

from autodist_tpu.chaos import hooks

__all__ = ["CATALOG", "FaultSpec", "make_handlers"]


@dataclass(frozen=True)
class FaultSpec:
    """One injectable fault class."""

    kind: str
    seam: str          # hooks.SEAM_* ("process" for launcher-level kills)
    description: str
    detects: str       # expected SNT/DOC code or typed outcome
    recovery: str      # the graceful-degradation / recovery contract


CATALOG: Dict[str, FaultSpec] = {s.kind: s for s in (
    FaultSpec(
        "nan_loss", hooks.SEAM_TRAIN_BATCH,
        "poison the training batch with NaN at step N (NaN gradients and "
        "loss by construction)",
        "SNT001 + DOC001",
        "restore the newest verified snapshot, replay clean steps; tail "
        "matches the uninterrupted run (elastic-resume tolerance)"),
    FaultSpec(
        "loss_spike", hooks.SEAM_TRAIN_BATCH,
        "scale the training batch by a large factor at step N (finite "
        "loss spike, z-score past threshold)",
        "SNT003 + DOC000",
        "restore the newest verified snapshot, replay clean steps; tail "
        "matches the uninterrupted run"),
    FaultSpec(
        "straggler", hooks.SEAM_AGG_SWEEP,
        "multiply one host's published step-time quantiles while the "
        "fault window is open",
        "SNT006 + HealthMonitor SUSPECT escalation",
        "score renormalizes when the window closes; the sentry episode "
        "re-arms (exactly one finding per episode)"),
    FaultSpec(
        "heartbeat_drop", hooks.SEAM_HB_PUBLISH,
        "drop one host's heartbeat publishes for the fault window "
        "(transport loss / network delay)",
        "peer HEALTHY -> SUSPECT -> DEAD transitions",
        "first fresh beat after the window returns the peer to HEALTHY "
        "(escalation backoff resets)"),
    FaultSpec(
        "heartbeat_partition", hooks.SEAM_HB_SWEEP,
        "hide every peer from the sweeping side (full partition: the "
        "observer sees a silent fleet)",
        "fleet_hung + hang bundle -> DOC003",
        "the launcher watchdog writes an attributable doctor bundle and "
        "terminates the fleet for a supervised restart"),
    FaultSpec(
        "snapshot_corrupt", hooks.SEAM_SNAPSHOT_WRITTEN,
        "flip one byte of a landed snapshot file after its manifest is "
        "written (bit rot / torn storage)",
        "verify() fails; ft_snapshots_corrupt_total increments",
        "latest_valid() falls back to the previous ring entry; restore "
        "succeeds from it"),
    FaultSpec(
        "snapshot_partial", hooks.SEAM_SNAPSHOT_WRITTEN,
        "truncate a landed snapshot file to half (partial write / full "
        "disk at the wrong moment)",
        "verify() fails; ft_snapshots_corrupt_total increments",
        "latest_valid() falls back to the previous ring entry"),
    FaultSpec(
        "snapshot_unwritable", hooks.SEAM_SNAPSHOT_WRITE,
        "raise OSError from the snapshot write path for the first K "
        "attempts (transient mount/permission loss)",
        "utils.retry heals it within policy (write retries counted); a "
        "permanent failure surfaces loudly via wait()",
        "snapshot lands on a retry attempt; no skipped ring slot"),
    FaultSpec(
        "serve_admission", hooks.SEAM_SERVE_ADMIT,
        "make engine admission defer (no free slot) while the window is "
        "open, backing the admission queue up",
        "typed REJECTED results with a reason + shed flight events "
        "(doctor timeline shows shed-load windows)",
        "queued work completes once the window closes; overflow is shed "
        "at the edge, nothing hangs"),
    FaultSpec(
        "page_exhaustion", hooks.SEAM_SERVE_PAGES,
        "report the KV page pool exhausted to every allocation while the "
        "window is open (a burst past pool capacity)",
        "admissions defer typed (requests stay QUEUED); queue overflow "
        "sheds typed REJECTED + shed flight events (doctor timeline shows "
        "the pressure window)",
        "pages recycle when the window closes: queued work completes, "
        "overflow was shed at the edge — no hang, no OOM"),
    FaultSpec(
        "eviction_storm", hooks.SEAM_SERVE_PAGES,
        "report the pool exhausted to every allocation while the window "
        "is open, against a prefix-cache engine holding a warm radix "
        "tree: sustained pressure forces eviction churn down to an "
        "empty tree before admission degrades",
        "admissions evict cold refcount-0 prefixes (prefix_stats "
        "evictions) then degrade typed (requests stay QUEUED); eviction "
        "never touches a live request's pages and no request ever reads "
        "another's KV (streams bit-identical)",
        "after the window admissions recompute the evicted prefixes and "
        "re-insert them; refcounts balance to zero at drain, pages "
        "leak-check to zero — eviction costs recompute, never "
        "correctness"),
    FaultSpec(
        "engine_death", hooks.SEAM_SERVE_STEP,
        "raise EngineDeadError from the decode step mid-batch",
        "every in-flight/queued request finished typed REJECTED with an "
        "engine-death reason; error event -> DOC006",
        "the batcher sheds all load with explicit rejections and stops; "
        "no client ever blocks in wait()"),
    FaultSpec(
        "worker_kill", "process",
        "SIGKILL a supervised fleet process mid-run (the harness child "
        "kills itself; no hook — the fault is the process dying)",
        "supervised restart with jittered exponential backoff",
        "restart budget and backoff reset on snapshot-ring progress; the "
        "relaunched attempt completes"),
    FaultSpec(
        "replica_death", hooks.SEAM_SERVE_STEP,
        "raise EngineDeadError from ONE replica's decode step "
        "(host-targeted) while the survivors keep serving behind the "
        "router",
        "replica self-reports DEAD; router failover — every in-flight "
        "request completes exactly once on survivors with the delivered "
        "stream bit-identical to an uninterrupted run; error event -> "
        "DOC006",
        "the router reroutes journaled work with prefix resume (the "
        "overlap token re-derived and asserted bit-equal); no duplicate "
        "delivery, no drop"),
    FaultSpec(
        "kill_mid_stochastic_stream", hooks.SEAM_SERVE_STEP,
        "raise EngineDeadError from ONE replica's decode step while it "
        "serves STOCHASTIC (temperature > 0) streams behind the router",
        "router failover resumes every sampled stream on a survivor with "
        "delivered tokens bit-identical to an uninterrupted control — "
        "the counter-based draws (serve/sampling.py) depend only on "
        "(request_id, seed, position), never on which replica, slot, or "
        "cache state produced them; error event -> DOC006",
        "journaled prefix resume re-derives the overlap token's draw "
        "from the same counter and asserts it bit-equal; exactly-once "
        "delivery holds for sampled streams exactly as for greedy"),
    FaultSpec(
        "kill_mid_quantized_stream", hooks.SEAM_SERVE_STEP,
        "raise EngineDeadError from ONE replica's decode step while it "
        "serves from int8 QUANTIZED KV pages behind the router",
        "router failover resumes every stream on a survivor with "
        "delivered tokens bit-identical to an uninterrupted quantized "
        "control — quantize-on-scatter is deterministic (amax/127 per "
        "(position, head)), so the survivor's re-prefill reproduces the "
        "dead replica's pages bit-exactly and the documented drift bound "
        "holds trivially across the failover; error event -> DOC006",
        "journaled prefix resume re-derives the overlap token against "
        "freshly quantized pages and asserts it bit-equal; exactly-once "
        "delivery holds for quantized serving exactly as for fp pages"),
    FaultSpec(
        "replica_partition", hooks.SEAM_HB_PUBLISH,
        "drop ONE replica's control-plane beats for the window (the "
        "replica itself keeps serving — a partition, not a death)",
        "router view READY -> SUSPECT; new work routed around the "
        "suspect",
        "beats resume -> READY -> routed again; work that stayed on the "
        "partitioned replica delivers exactly once (no duplicate, no "
        "drop, no spurious failover)"),
    FaultSpec(
        "draft_divergence", hooks.SEAM_SERVE_DRAFT,
        "garble the speculative-decode draft proposals (a seeded draft "
        "that proposes garbage) for the whole window",
        "acceptance collapses toward 0 (spec_stats / "
        "serve_spec_acceptance_rate); delivered streams stay "
        "bit-identical to plain greedy; no crash",
        "the target's verify program rejects every garbled proposal and "
        "still emits its own correct token each round — cadence degrades "
        "to ~1 token/round (bounded ITL), correctness and page "
        "accounting are untouched"),
    FaultSpec(
        "poisoned_calibration", hooks.SEAM_PILOT_REFIT,
        "corrupt one live calibration record at the pilot's refit intake "
        "(measured_s scaled by an adversarial factor) before the fit "
        "runs",
        "the pilot's fit-error regression gate (candidate graded on the "
        "TRUSTED records vs the pre-refit coefficients) rejects the "
        "refit; decision journal shows trigger -> rejected; the run "
        "stays DOC000",
        "persisted calibration coefficients unchanged (bit-equal) — the "
        "poisoned fit is never deployed; a subsequent clean refit "
        "proceeds normally (keep-best in plan/calibrate.py is the "
        "second, independent guard)"),
    FaultSpec(
        "rolling_upgrade_under_load", "process",
        "drain + restart every replica in turn under sustained traffic "
        "(no hook — the 'fault' is the upgrade itself)",
        "zero dropped requests; only typed shed; p99 bounded; every "
        "replica restarted exactly once",
        "each drained replica's leftovers fail over through the journal "
        "(ids + delivered watermarks); the restarted replica re-admits "
        "on its READY beat"),
)}


# ------------------------------------------------------------- injectors
def _poison_tree(tree, fill=None, scale=None):
    """NaN-fill or scale every floating leaf (jax or numpy)."""
    import numpy as np

    import jax

    def leaf(x):
        a = np.asarray(x)
        if not np.issubdtype(a.dtype, np.floating):
            return x
        if fill is not None:
            return np.full_like(a, fill)
        return a * np.asarray(scale, a.dtype)

    return jax.tree.map(leaf, tree)


def make_handlers(plant) -> Dict[str, Callable]:
    """Build the seam->hook map for ``plant``'s schedule. Only seams whose
    faults actually appear in the schedule get handlers, so an installed
    plant perturbs nothing it was not asked to."""
    seams = {CATALOG[e.fault].seam for e in plant.schedule.events
             if e.fault in CATALOG}
    handlers: Dict[str, Callable] = {}

    def events(seam: str, step=None) -> List:
        return [e for e in plant.schedule.events
                if CATALOG.get(e.fault) is not None
                and CATALOG[e.fault].seam == seam
                and e.active(plant.step if step is None else step)]

    if hooks.SEAM_TRAIN_BATCH in seams:
        def train_batch(batch, num_steps=1, **_):
            # A window [step, step+num_steps) is poisoned when any of its
            # steps falls inside an event window; the harness uses
            # num_steps=1 so injection is per-step exact.
            for e in plant.schedule.events:
                if CATALOG[e.fault].seam != hooks.SEAM_TRAIN_BATCH:
                    continue
                if not any(e.active(plant.step + i)
                           for i in range(max(1, int(num_steps)))):
                    continue
                if e.fault == "nan_loss":
                    plant.record("nan_loss", detail="batch poisoned with NaN")
                    batch = _poison_tree(batch, fill=float("nan"))
                elif e.fault == "loss_spike":
                    scale = float(e.param("scale", 64.0))
                    plant.record("loss_spike", detail=f"batch scaled x{scale:g}")
                    batch = _poison_tree(batch, scale=scale)
            return batch

        handlers[hooks.SEAM_TRAIN_BATCH] = train_batch

    # The metrics seam always installs alongside train faults: it is where
    # the plant's step counter advances (post-window), keeping batch and
    # metrics views of "the current step" consistent.
    if hooks.SEAM_TRAIN_BATCH in seams:
        def train_metrics(metrics, num_steps=1, **_):
            plant.advance(max(1, int(num_steps)))
            return metrics

        handlers[hooks.SEAM_TRAIN_METRICS] = train_metrics

    if hooks.SEAM_HB_PUBLISH in seams:
        def hb_publish(payload, process_id=0, **_):
            for e in events(hooks.SEAM_HB_PUBLISH):
                if e.fault == "heartbeat_drop" and int(e.host) == int(process_id):
                    plant.record("heartbeat_drop", host=int(process_id))
                    return None  # the beat never lands
                if (e.fault == "replica_partition"
                        and int(e.host) == int(process_id)):
                    # record_once: replica heartbeat threads publish on a
                    # wall-clock cadence, so a per-drop trace would be
                    # timing-dependent — one entry per window keeps the
                    # trace replay-deterministic.
                    plant.record_once(("replica_partition", e.at_step,
                                       int(process_id)),
                                      "replica_partition",
                                      host=int(process_id),
                                      detail="control-plane beats dropped")
                    return None
            return payload

        handlers[hooks.SEAM_HB_PUBLISH] = hb_publish

    if hooks.SEAM_HB_SWEEP in seams:
        def hb_sweep(board, **_):
            for e in events(hooks.SEAM_HB_SWEEP):
                if e.fault == "heartbeat_partition":
                    plant.record_once(("heartbeat_partition", e.at_step),
                                      "heartbeat_partition",
                                      detail=f"hiding {len(board)} peer(s)")
                    return {}
            return board

        handlers[hooks.SEAM_HB_SWEEP] = hb_sweep

    if hooks.SEAM_AGG_SWEEP in seams:
        def agg_sweep(fleet, **_):
            for e in events(hooks.SEAM_AGG_SWEEP):
                if e.fault != "straggler":
                    continue
                host = int(e.host)
                summary = fleet.get(host)
                if isinstance(summary, dict):
                    scale = float(e.param("scale", 3.0))
                    slowed = dict(summary)
                    for k in ("p50", "p90", "p99", "mean"):
                        if k in slowed:
                            slowed[k] = float(slowed[k]) * scale
                    fleet = {**fleet, host: slowed}
                    plant.record_once(("straggler", e.at_step, host),
                                      "straggler", host=host,
                                      detail=f"p50 x{scale:g}")
            return fleet

        handlers[hooks.SEAM_AGG_SWEEP] = agg_sweep

    if hooks.SEAM_SNAPSHOT_WRITE in seams:
        def snapshot_write(path="", step=None, **_):
            for e in events(hooks.SEAM_SNAPSHOT_WRITE):
                if e.fault != "snapshot_unwritable":
                    continue
                times = int(e.param("times", 1))
                used = plant.state.setdefault(("unwritable", id(e)), 0)
                if used < times:
                    plant.state[("unwritable", id(e))] = used + 1
                    plant.record("snapshot_unwritable", step=step,
                                 detail=f"write attempt {used + 1} refused")
                    raise OSError(
                        f"chaos: snapshot dir unwritable (injected, "
                        f"attempt {used + 1}/{times})")

        handlers[hooks.SEAM_SNAPSHOT_WRITE] = snapshot_write

    if hooks.SEAM_SNAPSHOT_WRITTEN in seams:
        def snapshot_written(path="", step=None, **_):
            for e in events(hooks.SEAM_SNAPSHOT_WRITTEN):
                if e.fault not in ("snapshot_corrupt", "snapshot_partial"):
                    continue
                names = sorted(
                    os.path.join(r, f)
                    for r, _, fs in os.walk(path) for f in fs
                    if f != "MANIFEST.json")
                if not names:
                    continue
                victim = names[plant.rng.randrange(len(names))]
                size = os.path.getsize(victim)
                if size <= 0:
                    continue
                rel = os.path.relpath(victim, path)
                if e.fault == "snapshot_corrupt":
                    offset = plant.rng.randrange(size)
                    with open(victim, "r+b") as f:
                        f.seek(offset)
                        byte = f.read(1)
                        f.seek(offset)
                        f.write(bytes([(byte[0] ^ 0xFF) if byte else 0xFF]))
                    plant.record("snapshot_corrupt", step=step, file=rel,
                                 detail=f"flipped byte {offset}")
                else:
                    with open(victim, "r+b") as f:
                        f.truncate(size // 2)
                    plant.record("snapshot_partial", step=step, file=rel,
                                 detail=f"truncated {size} -> {size // 2}")

        handlers[hooks.SEAM_SNAPSHOT_WRITTEN] = snapshot_written

    if hooks.SEAM_SERVE_ADMIT in seams:
        def serve_admit(**_):
            for e in events(hooks.SEAM_SERVE_ADMIT):
                if e.fault == "serve_admission":
                    plant.record_once(("serve_admission", e.at_step),
                                      "serve_admission",
                                      detail="admission deferred")
                    return "defer"
            return None

        handlers[hooks.SEAM_SERVE_ADMIT] = serve_admit

    if hooks.SEAM_SERVE_PAGES in seams:
        def serve_pages(**_):
            for e in events(hooks.SEAM_SERVE_PAGES):
                if e.fault == "page_exhaustion":
                    plant.record_once(("page_exhaustion", e.at_step),
                                      "page_exhaustion",
                                      detail="pool reported exhausted")
                    return "exhaust"
                if e.fault == "eviction_storm":
                    # Same directive, different victim: against a
                    # prefix-cache engine the evict-retry loop drains the
                    # radix tree (churn) before the typed None lands.
                    plant.record_once(("eviction_storm", e.at_step),
                                      "eviction_storm",
                                      detail="sustained pool pressure")
                    return "exhaust"

        handlers[hooks.SEAM_SERVE_PAGES] = serve_pages

    if hooks.SEAM_SERVE_DRAFT in seams:
        def serve_draft(host=0, **_):
            for e in events(hooks.SEAM_SERVE_DRAFT):
                if (e.fault == "draft_divergence"
                        and int(e.host) == int(host)):
                    # record_once: the seam fires every spec round from a
                    # scheduler thread — one trace entry per window keeps
                    # replay byte-deterministic (the garbling itself is a
                    # deterministic offset, no RNG draw needed).
                    plant.record_once(("draft_divergence", e.at_step,
                                       int(host)),
                                      "draft_divergence", host=int(host),
                                      detail="draft proposals garbled")
                    return "garbage"
            return None

        handlers[hooks.SEAM_SERVE_DRAFT] = serve_draft

    if hooks.SEAM_PILOT_REFIT in seams:
        def pilot_refit(records, **_):
            from dataclasses import replace as _replace

            for e in events(hooks.SEAM_PILOT_REFIT):
                if e.fault != "poisoned_calibration" or not records:
                    continue
                scale = float(e.param("scale", 1000.0))
                idx = plant.rng.randrange(len(records))
                records = list(records)
                records[idx] = _replace(
                    records[idx],
                    measured_s=float(records[idx].measured_s) * scale)
                plant.record("poisoned_calibration", index=idx,
                             detail=f"measured_s x{scale:g}")
            return records

        handlers[hooks.SEAM_PILOT_REFIT] = pilot_refit

    if hooks.SEAM_SERVE_STEP in seams:
        def serve_step(host=0, **_):
            for e in events(hooks.SEAM_SERVE_STEP):
                if e.fault == "engine_death":
                    from autodist_tpu.serve.engine import EngineDeadError

                    plant.record_once(("engine_death", e.at_step),
                                      "engine_death",
                                      detail="decode step raised")
                    raise EngineDeadError(
                        "chaos: injected engine death mid-decode")
                if (e.fault == "replica_death"
                        and int(e.host) == int(host)):
                    from autodist_tpu.serve.engine import EngineDeadError

                    plant.record_once(("replica_death", e.at_step,
                                       int(host)),
                                      "replica_death", host=int(host),
                                      detail="decode step raised")
                    raise EngineDeadError(
                        f"chaos: injected replica {host} death mid-decode")
                if (e.fault == "kill_mid_stochastic_stream"
                        and int(e.host) == int(host)):
                    from autodist_tpu.serve.engine import EngineDeadError

                    plant.record_once(("kill_mid_stochastic_stream",
                                       e.at_step, int(host)),
                                      "kill_mid_stochastic_stream",
                                      host=int(host),
                                      detail="decode step raised mid-"
                                             "stochastic-stream")
                    raise EngineDeadError(
                        f"chaos: injected replica {host} death mid-"
                        f"stochastic-stream")
                if (e.fault == "kill_mid_quantized_stream"
                        and int(e.host) == int(host)):
                    from autodist_tpu.serve.engine import EngineDeadError

                    plant.record_once(("kill_mid_quantized_stream",
                                       e.at_step, int(host)),
                                      "kill_mid_quantized_stream",
                                      host=int(host),
                                      detail="decode step raised mid-"
                                             "quantized-stream")
                    raise EngineDeadError(
                        f"chaos: injected replica {host} death mid-"
                        f"quantized-stream")

        handlers[hooks.SEAM_SERVE_STEP] = serve_step

    return handlers
