"""Measured flash-attention crossover: when "auto" picks the Pallas kernel.

``examples/benchmark/flash_crossover.py`` sweeps the transformer step with
``attention_impl`` "dot" vs "flash" over sequence lengths on the real
accelerator and records the table in ``docs/measured/flash_crossover.json``.
The shape of that table (TPU v5e, bf16): XLA's fused dot-product attention
wins at short sequences (the flash kernel's block bookkeeping costs more
than the O(s²) logits it avoids materializing), and the Pallas kernel wins
once the logits matrix stops fitting in VMEM — 2× step time at s=4096.

This module turns the table into the ONE decision rule the transformer's
``attention_impl="auto"`` uses: the smallest measured sequence length from
which flash never loses to dot again. Below it, or when the sequence is not
block-aligned (the kernel would fall back to the jnp reference anyway),
"auto" resolves to "dot".
"""
from __future__ import annotations

import json
import os
from typing import Optional

#: Fallback when no measured table is readable: the v5e-measured breakeven
#: (flash ties dot at s=1024 and wins beyond; docs/measured/
#: flash_crossover.json).
DEFAULT_FLASH_CROSSOVER_SEQ = 1024

#: The flash kernel's block alignment (ops/flash_attention.py falls back to
#: the jnp reference for sequences this doesn't divide).
_FLASH_BLOCK = 128

_cache: dict = {}


def _measured_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "docs", "measured", "flash_crossover.json")


def flash_crossover_seq(path: Optional[str] = None) -> int:
    """Smallest measured seq length from which "flash" never loses to
    "dot" (tokens/sec), per the recorded sweep; the packaged default when
    the file is missing, unreadable, or records no crossover. Cached per
    path — the resolution runs inside model tracing."""
    key = path or "__default__"
    if key in _cache:
        return _cache[key]
    out = DEFAULT_FLASH_CROSSOVER_SEQ
    try:
        with open(path or _measured_path(), "r", encoding="utf-8") as f:
            rows = json.load(f).get("rows", [])
        by_seq: dict = {}
        for r in rows:
            by_seq.setdefault(int(r["seq"]), {})[str(r["impl"])] = float(
                r["tokens_per_sec"])
        seqs = sorted(s for s, v in by_seq.items()
                      if "dot" in v and "flash" in v)
        for i, s in enumerate(seqs):
            if all(by_seq[t]["flash"] >= by_seq[t]["dot"]
                   for t in seqs[i:]):
                out = s
                break
    except (OSError, ValueError, KeyError, TypeError):
        pass  # unmeasured installs use the packaged default
    _cache[key] = out
    return out


def resolve_attention_impl(impl: str, seq_len: int) -> str:
    """The ``attention_impl="auto"`` rule: "flash" at and above the
    measured crossover when the sequence is block-aligned (the Pallas
    kernel's own constraint), else "dot". Explicit impls pass through
    untouched — "auto" never overrides a caller's choice."""
    if impl != "auto":
        return impl
    if seq_len >= flash_crossover_seq() and seq_len % _FLASH_BLOCK == 0:
        return "flash"
    return "dot"
