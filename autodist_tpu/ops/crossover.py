"""Measured attention crossovers: when "auto" picks a Pallas kernel.

``examples/benchmark/flash_crossover.py`` sweeps the transformer step with
``attention_impl`` "dot" vs "flash" over sequence lengths on the real
accelerator and records the table in ``docs/measured/flash_crossover.json``.
The shape of that table (TPU v5e, bf16): XLA's fused dot-product attention
wins at short sequences (the flash kernel's block bookkeeping costs more
than the O(s²) logits it avoids materializing), and the Pallas kernel wins
once the logits matrix stops fitting in VMEM — 2× step time at s=4096.

This module turns the table into the ONE decision rule the transformer's
``attention_impl="auto"`` uses: the smallest measured sequence length from
which flash never loses to dot again. Below it, or when the sequence is not
block-aligned (the kernel would fall back to the jnp reference anyway),
"auto" resolves to "dot".

The serving stack's ``paged_attention_impl="auto"`` gets the same treatment:
``examples/benchmark/paged_crossover.py`` sweeps decode steps with the
paged-attention gather vs the page-walking pallas kernel
(ops/paged_attention.py) over (batch, table width, heads) shapes and records
``docs/measured/paged_crossover.json``; :func:`resolve_paged_impl` picks
"kernel" from the smallest timeline at which the kernel never loses for the
nearest recorded (batch, heads) bucket. Off-TPU, "auto" always resolves to
"gather" — interpret-mode pallas is a correctness vehicle, not a fast path.
"""
from __future__ import annotations

import json
import os
from typing import Optional

#: Fallback when no measured table is readable: the v5e-measured breakeven
#: (flash ties dot at s=1024 and wins beyond; docs/measured/
#: flash_crossover.json).
DEFAULT_FLASH_CROSSOVER_SEQ = 1024

#: The flash kernel's block alignment (ops/flash_attention.py falls back to
#: the jnp reference for sequences this doesn't divide).
_FLASH_BLOCK = 128

_cache: dict = {}


def _measured_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "docs", "measured", "flash_crossover.json")


def flash_crossover_seq(path: Optional[str] = None) -> int:
    """Smallest measured seq length from which "flash" never loses to
    "dot" (tokens/sec), per the recorded sweep; the packaged default when
    the file is missing, unreadable, or records no crossover. Cached per
    path — the resolution runs inside model tracing."""
    key = path or "__default__"
    if key in _cache:
        return _cache[key]
    out = DEFAULT_FLASH_CROSSOVER_SEQ
    try:
        with open(path or _measured_path(), "r", encoding="utf-8") as f:
            rows = json.load(f).get("rows", [])
        by_seq: dict = {}
        for r in rows:
            by_seq.setdefault(int(r["seq"]), {})[str(r["impl"])] = float(
                r["tokens_per_sec"])
        seqs = sorted(s for s, v in by_seq.items()
                      if "dot" in v and "flash" in v)
        for i, s in enumerate(seqs):
            if all(by_seq[t]["flash"] >= by_seq[t]["dot"]
                   for t in seqs[i:]):
                out = s
                break
    except (OSError, ValueError, KeyError, TypeError):
        pass  # unmeasured installs use the packaged default
    _cache[key] = out
    return out


def resolve_attention_impl(impl: str, seq_len: int) -> str:
    """The ``attention_impl="auto"`` rule: "flash" at and above the
    measured crossover when the sequence is block-aligned (the Pallas
    kernel's own constraint), else "dot". Explicit impls pass through
    untouched — "auto" never overrides a caller's choice."""
    if impl != "auto":
        return impl
    if seq_len >= flash_crossover_seq() and seq_len % _FLASH_BLOCK == 0:
        return "flash"
    return "dot"


# ------------------------------------------------------- paged kernel-vs-gather
#: Fallback paged crossover when no measured table is readable: the timeline
#: width (table pages * page_len) from which the page-walking kernel beats
#: the materialize-then-attend gather (docs/measured/paged_crossover.json).
DEFAULT_PAGED_CROSSOVER_TIMELINE = 1024


def _paged_measured_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "docs", "measured", "paged_crossover.json")


def paged_crossover_timeline(batch: Optional[int] = None,
                             heads: Optional[int] = None,
                             path: Optional[str] = None) -> int:
    """Smallest measured timeline width from which "kernel" never loses to
    "gather" (tokens/sec) for the nearest recorded (batch, heads) bucket;
    the packaged default when the file is missing, unreadable, or records
    no crossover. Cached per (path, batch, heads) — the resolution runs
    inside the serving programs' tracing."""
    key = ("paged", path or "__default__", batch, heads)
    if key in _cache:
        return _cache[key]
    out = DEFAULT_PAGED_CROSSOVER_TIMELINE
    try:
        with open(path or _paged_measured_path(), "r",
                  encoding="utf-8") as f:
            rows = json.load(f).get("rows", [])
        # Nearest recorded (batch, heads) bucket: the sweep records a few
        # decode-shaped points, not the full cross product.
        def _dist(r):
            d = 0.0
            if batch is not None and "batch" in r:
                d += abs(float(r["batch"]) - batch)
            if heads is not None and "heads" in r:
                d += abs(float(r["heads"]) - heads)
            return d
        if rows and (batch is not None or heads is not None):
            best = min(_dist(r) for r in rows)
            rows = [r for r in rows if _dist(r) == best]
        by_tl: dict = {}
        for r in rows:
            tl = int(r["table_pages"]) * int(r["page_len"])
            by_tl.setdefault(tl, {})[str(r["impl"])] = float(
                r["tokens_per_sec"])
        tls = sorted(t for t, v in by_tl.items()
                     if "gather" in v and "kernel" in v)
        for i, t in enumerate(tls):
            if all(by_tl[u]["kernel"] >= by_tl[u]["gather"]
                   for u in tls[i:]):
                out = t
                break
    except (OSError, ValueError, KeyError, TypeError):
        pass  # unmeasured installs use the packaged default
    _cache[key] = out
    return out


def resolve_paged_impl(impl: str, batch: int, table_pages: int,
                       page_len: int, heads: int) -> str:
    """The ``paged_attention_impl="auto"`` rule: "kernel" at and above the
    measured timeline crossover for the nearest recorded (batch, heads)
    shape — on TPU only; off-TPU "auto" is always "gather" (interpret-mode
    pallas is the tier-1 correctness vehicle, ~100x slower than the XLA
    gather). Explicit impls pass through untouched, so tests force the
    kernel on CPU and devices force the gather for A/B sweeps."""
    if impl != "auto":
        return impl
    import jax  # lazy: keep module import free of a backend query

    if jax.default_backend() != "tpu":
        return "gather"
    if table_pages * page_len >= paged_crossover_timeline(batch, heads):
        return "kernel"
    return "gather"
