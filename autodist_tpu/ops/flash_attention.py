"""Flash attention for TPU: pallas forward + backward kernels, custom VJP.

Online-softmax attention (Dao et al., arXiv 2205.14135) laid out for the TPU
memory hierarchy: queries stream through VMEM in blocks, K/V live in VMEM per
(batch*head) slice, the softmax accumulators stay fp32 while matmuls hit the
MXU in the input dtype. Backward is the standard two-kernel scheme (dkdv
gridded over K blocks, dq over Q blocks) with the forward logsumexp saved as
residual.

Layout contract: q, k, v are [batch, seq, heads, head_dim] (the transformer's
natural shape); internally folded to [batch*heads, seq, head_dim].

On CPU the kernels run in pallas interpret mode (tests exercise the same
kernel logic); non-block-aligned sequence lengths fall back to the jnp
reference implementation.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def mha_reference(q, k, v, causal: bool = False):
    """jnp reference implementation ([B,S,H,D] layout), fp32 softmax."""
    head_dim = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(head_dim).astype(jnp.float32)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool, scale: float):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    MXU work stays in the input dtype (bf16 in, fp32 accumulate via
    preferred_element_type); only the softmax stats are fp32. Stats are kept
    [bq, 1]-shaped — 1D vectors force Mosaic relayouts.
    """
    q = q_ref[0]                                       # [bq, d], input dtype
    block_q, head_dim = q.shape
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k
    qi = pl.program_id(1)
    q_start = qi * block_q

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    def body(kb, carry):
        m, l, acc = carry
        k_start = kb * block_k
        kblk = k_ref[0, pl.ds(k_start, block_k), :]
        vblk = v_ref[0, pl.ds(k_start, block_k), :]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                      # [bq, bk] fp32
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # Only blocks intersecting the causal triangle: k_start <= q_end.
        last_kb = (q_start + block_q - 1) // block_k + 1
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


# ------------------------------------------------------------------ backward
def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, *, block_q: int, causal: bool, scale: float):
    """One (batch*head, k-block) program: accumulate dK, dV over Q blocks."""
    kblk = k_ref[0].astype(jnp.float32)               # [bk, d]
    vblk = v_ref[0].astype(jnp.float32)
    block_k, head_dim = kblk.shape
    seq_q = q_ref.shape[1]
    num_qb = seq_q // block_q
    ki = pl.program_id(1)
    k_start = ki * block_k

    dk0 = jnp.zeros((block_k, head_dim), jnp.float32)
    dv0 = jnp.zeros((block_k, head_dim), jnp.float32)

    def body(qb, carry):
        dk, dv = carry
        q_start = qb * block_q
        q = q_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(q_start, block_q), 0]
        delta = delta_ref[0, pl.ds(q_start, block_q), 0]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [bq, bk]
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                  # [bq, bk]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [bq, bk]
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_new, dv_new

    if causal:
        first_qb = k_start // block_q
    else:
        first_qb = 0
    dk, dv = jax.lax.fori_loop(first_qb, num_qb, body, (dk0, dv0))
    # q rows were pre-scaled, so dk = ds^T @ (q*scale) is already dL/dK.
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, block_k: int, causal: bool, scale: float):
    """One (batch*head, q-block) program: accumulate dQ over K blocks."""
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    block_q, head_dim = q.shape
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k
    qi = pl.program_id(1)
    q_start = qi * block_q

    dq0 = jnp.zeros((block_q, head_dim), jnp.float32)

    def body(kb, dq):
        k_start = kb * block_k
        kblk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    last_kb = ((q_start + block_q - 1) // block_k + 1) if causal else num_kb
    dq = jax.lax.fori_loop(0, last_kb, body, dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


# ---------------------------------------------------------------- dispatcher
def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fold_heads(x):
    # [b, s, h, d] -> [b*h, s, d]
    b, s, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)


def _unfold_heads(x, b, h):
    bh, s, d = x.shape
    return jnp.transpose(x.reshape(b, h, s, d), (0, 2, 1, 3))


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention(
    q, k, v,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
):
    """Flash attention, [B, S, H, D] in/out. Differentiable (custom VJP)."""
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _pallas_forward(q3, k3, v3, causal, block_q, block_k, interpret):
    bh, seq_q, head_dim = q3.shape
    seq_k = k3.shape[1]
    scale = 1.0 / (head_dim ** 0.5)
    grid = (bh, seq_q // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, causal=causal, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, head_dim), q3.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse


def _use_reference(q, k, block_q, block_k) -> bool:
    # Conservative: require block-aligned sequences (TPU tile constraint is
    # last-two block dims divisible by (8, 128) or equal to the array dims;
    # checking against the *uncapped* block size keeps odd lengths off the
    # kernel path entirely).
    seq_q, seq_k = q.shape[1], k.shape[1]
    return (
        seq_q % min(block_q, seq_q) != 0
        or seq_k % min(block_k, seq_k) != 0
        or seq_q % 128 != 0
        or seq_k % 128 != 0
        or seq_q != seq_k
    )


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = _should_interpret()
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, k.shape[1])
    if _use_reference(q, k, block_q, block_k):
        out = mha_reference(q, k, v, causal)
        return out, (q, k, v, out, None)
    q3, k3, v3 = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    out3, lse = _pallas_forward(q3, k3, v3, causal, block_q, block_k, interpret)
    return _unfold_heads(out3, b, h), (q, k, v, _unfold_heads(out3, b, h), lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if interpret is None:
        interpret = _should_interpret()
    if lse is None:
        # Reference fallback path: differentiate the reference impl.
        def ref(q_, k_, v_):
            return mha_reference(q_, k_, v_, causal)

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)

    b, s, h, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    scale = 1.0 / (d ** 0.5)
    q3, k3, v3 = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    o3, do3 = _fold_heads(out), _fold_heads(g)
    bh, seq, _ = q3.shape
    # delta = rowsum(dO * O): cheap elementwise+reduce, XLA fuses it.
    delta = (o3.astype(jnp.float32) * do3.astype(jnp.float32)).sum(-1)[..., None]

    dk3, dv3 = pl.pallas_call(
        functools.partial(_dkdv_kernel, block_q=bq, causal=causal, scale=scale),
        grid=(bh, seq // bk),
        in_specs=[
            pl.BlockSpec((1, seq, d), lambda b_, i: (b_, 0, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda b_, i: (b_, i, 0)),    # k block
            pl.BlockSpec((1, bk, d), lambda b_, i: (b_, i, 0)),    # v block
            pl.BlockSpec((1, seq, d), lambda b_, i: (b_, 0, 0)),   # do
            pl.BlockSpec((1, seq, 1), lambda b_, i: (b_, 0, 0)),   # lse
            pl.BlockSpec((1, seq, 1), lambda b_, i: (b_, 0, 0)),   # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b_, i: (b_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq, d), v.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    dq3 = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=bk, causal=causal, scale=scale),
        grid=(bh, seq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b_, i: (b_, i, 0)),    # q block
            pl.BlockSpec((1, seq, d), lambda b_, i: (b_, 0, 0)),   # k
            pl.BlockSpec((1, seq, d), lambda b_, i: (b_, 0, 0)),   # v
            pl.BlockSpec((1, bq, d), lambda b_, i: (b_, i, 0)),    # do block
            pl.BlockSpec((1, bq, 1), lambda b_, i: (b_, i, 0)),    # lse block
            pl.BlockSpec((1, bq, 1), lambda b_, i: (b_, i, 0)),    # delta block
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b_, i: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    return (
        _unfold_heads(dq3, b, h),
        _unfold_heads(dk3, b, h),
        _unfold_heads(dv3, b, h),
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)
