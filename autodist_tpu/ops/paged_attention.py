"""Paged attention for TPU — the ONE home for softmax-over-pages math.

Every serving-path attention over the paged KV pool lives here (enforced by
``tools/check_patterns.py`` rule 12): the gather reference implementations the
compiled programs shipped with since PR 12, and the pallas kernel that walks
each row's page table block-by-block directly in HBM — online softmax per
page (Dao et al., arXiv 2205.14135, rendered over pages instead of contiguous
K blocks), the position mask folded into the block loop, no materialized
``[B, P * page_len, H, D]`` timeline. Three entry points match the engine's
compiled programs: decode step (one query per row), spec verify (K+1 queries
per row), and prefill-chunk (one row, C queries).

Underneath either impl sits optional int8 KV quantization with per-position
per-head scales (``quantize_kv`` / ``dequantize_kv``): pages store int8 plus
an f32 scale row, quantize-on-scatter happens in the model forwards,
dequantize happens on gather or inside the kernel block loop. At
``head_dim=64`` a KV position costs 68 bytes/head (64 int8 + 4 scale) vs 256
f32 (3.76x) or 128 bf16 (1.88x) — the effective-capacity math the analyzer
and selftest assert.

Correctness contract (tests/test_paged_kernel.py, serve --selftest):
- quant OFF: kernel token streams bit-identical to the gather path (the
  gather path itself is bit-identical to the pre-kernel programs — the
  einsum spellings below are verbatim);
- quant ON: logit drift vs the fp oracle bounded (documented in
  docs/serving.md), draft and verify run against the SAME quantized pages so
  spec-decode losslessness is preserved.

Impl selection is measured, not assumed: ``autodist_tpu.ops.crossover.
resolve_paged_impl`` picks kernel-vs-gather per (batch, table width, heads)
shape from the recorded sweep in ``docs/measured/paged_crossover.json``.

On CPU the kernel runs in pallas interpret mode (the tier-1 parity suite
exercises the same kernel logic the TPU compiles).
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The masking constant every forward path shares. -1e30 is kept verbatim for
# f32 logits (bit-identity with the pre-hoist programs); non-f32 logits get a
# finite value well inside the dtype's range — a literal -1e30 overflows
# float16 to -inf and makes fully-masked rows NaN (inf - inf) instead of
# uniform, which is the footgun this helper retires.
NEG_INF = -1e30


def mask_value(dtype: Any = jnp.float32) -> float:
    """The additive-mask fill value for logits of ``dtype``."""
    dtype = jnp.dtype(dtype)
    if dtype in (jnp.dtype(jnp.float32), jnp.dtype(jnp.float64)):
        return NEG_INF
    # Half of the finite minimum: representable, and far enough below any
    # real logit that softmax still zeroes the masked entries.
    return float(jnp.finfo(dtype).min) / 2.0


def position_mask(timeline: int, positions):
    """``True`` where timeline slot ``t <= positions[...]``.

    ``positions`` is ``[B]`` (decode), ``[C]`` (prefill-chunk absolute
    positions) or ``[B, K1]`` (verify rows); the mask gains a trailing
    timeline axis: ``positions.shape + (timeline,)``. Pad/scratch timeline
    slots always sit at or past a request's capacity — strictly above any
    live position — so this one comparison is the whole safety story for
    garbage pages (serve/pages.py SCRATCH_PAGE).
    """
    return jnp.arange(timeline) <= positions[..., None]


def apply_mask(logits, mask):
    """Fill ``~mask`` with the dtype-safe mask value (mask pre-broadcast)."""
    return jnp.where(mask, logits, mask_value(logits.dtype))


# ------------------------------------------------------------ quantization
def quantize_kv(x):
    """Symmetric int8 quantization over the head_dim axis.

    ``x [..., H, D]`` -> ``(int8 [..., H, D], f32 scale [..., H])`` with
    ``scale = amax(|x|) / 127`` per (position, head) row. All-zero rows keep
    scale 0 (dequantizes to exact zeros). Pure function of the input —
    deterministic, so failover re-prefill reproduces identical pages and the
    journal-replay bit-identity contract survives quantization.
    """
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = amax / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(x32 / safe[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype: Any = jnp.float32):
    """Inverse of :func:`quantize_kv`: ``int8 * scale`` cast to ``dtype``."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


# ------------------------------------------------------- gather reference
def _paged_gather(cache_layer, page_tables):
    """Gather one layer's KV timeline(s) by page index.

    ``cache_layer [n_pages, page_len, H, D]`` (or ``[n_pages, page_len, H]``
    for a scale plane); ``page_tables`` is ``[P]`` (one request) or ``[B, P]``
    (the decode batch). Returns the gathered timeline
    ``[..., P * page_len, ...]``. Pad entries point at the scratch page —
    finite garbage the caller's position mask excludes.
    """
    page_len = cache_layer.shape[1]
    tail = cache_layer.shape[2:]
    gathered = cache_layer[page_tables]          # [..., P, page_len, ...]
    return gathered.reshape(
        page_tables.shape[:-1] + (page_tables.shape[-1] * page_len,) + tail)


def _gather_timeline(pages, scale, page_tables, compute_dtype):
    """Materialize the timeline in ``compute_dtype``, dequantizing if
    ``scale`` is present. The fp branch is the verbatim pre-kernel gather."""
    if scale is None:
        return _paged_gather(pages, page_tables).astype(compute_dtype)
    g = _paged_gather(pages, page_tables)
    s = _paged_gather(scale, page_tables)
    return dequantize_kv(g, s, compute_dtype)


# ------------------------------------------------------------ pallas kernel
def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _paged_kernel(tables_ref, qpos_ref, q_ref, *rest, page_len: int,
                  n_tables: int, quantized: bool, scale: float):
    """One (row, page) program: stream the row's pages, online softmax.

    Grid is ``(B, P)`` with the page dimension minor — for a fixed row the
    pages run sequentially, carrying fp32 (m, l, acc) stats in VMEM scratch
    across iterations (init at p == 0, finalize at p == P - 1). The k/v
    BlockSpec index maps read ``tables_ref`` (scalar-prefetch) so each step
    DMAs exactly one page out of HBM: traffic scales with the live table,
    never with a materialized ``[B, P * page_len, H, D]`` timeline.

    The position mask is folded into the block loop via the absolute slot
    index ``t = p * page_len + offset``; fully-masked pages contribute
    exp(NEG_INF - m) == 0 because slot 0 (always admitted: positions >= 0)
    seeds ``m`` with a finite logit on the first page.
    """
    if quantized:
        k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = rest
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                   # [Q, H, D]
    n_q = q.shape[0]
    qh = jnp.transpose(q, (1, 0, 2)).astype(jnp.float32)   # [H, Q, D]
    kblk = k_ref[0]                                # [page_len, H, D]
    vblk = v_ref[0]
    if quantized:
        kf = kblk.astype(jnp.float32) * ks_ref[0][..., None]
        vf = vblk.astype(jnp.float32) * vs_ref[0][..., None]
    else:
        kf = kblk.astype(jnp.float32)
        vf = vblk.astype(jnp.float32)
    kh = jnp.transpose(kf, (1, 0, 2))              # [H, T, D]
    vh = jnp.transpose(vf, (1, 0, 2))
    s = jax.lax.dot_general(
        qh, kh, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * scale                                      # [H, Q, T] fp32
    t_abs = p * page_len + jax.lax.broadcasted_iota(
        jnp.int32, (n_q, page_len), 1)
    qpos = qpos_ref[0]                             # [Q] int32
    admit = t_abs <= qpos[:, None]                 # [Q, T]
    s = jnp.where(admit[None, :, :], s, NEG_INF)

    m = m_ref[...]                                 # [H, Q, 1]
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    pexp = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_ref[...] = alpha * l_ref[...] + pexp.sum(axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        pexp, vh, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                              # [H, Q, D]
    m_ref[...] = m_new

    @pl.when(p == n_tables - 1)
    def _finalize():
        l = l_ref[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_ref[...] / l_safe                # [H, Q, D]
        o_ref[0] = jnp.transpose(out, (1, 0, 2)).astype(o_ref.dtype)


def _kernel_attention(q4, k_pages, v_pages, page_tables, q_positions,
                      k_scale, v_scale, interpret: Optional[bool]):
    """Dispatch the unified kernel: ``q4 [B, Q, H, D]``, ``page_tables
    [B, P]``, ``q_positions [B, Q]`` absolute positions per query. Returns
    ``[B, Q, H, D]`` in the query dtype."""
    if interpret is None:
        interpret = _should_interpret()
    b, n_q, h, d = q4.shape
    page_len = k_pages.shape[1]
    n_tables = page_tables.shape[1]
    quantized = k_scale is not None
    scale = 1.0 / (d ** 0.5)
    tables = page_tables.astype(jnp.int32)
    qpos = q_positions.astype(jnp.int32)

    page_spec = pl.BlockSpec(
        (1, page_len, h, d), lambda bi, pi, t: (t[bi, pi], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, n_q), lambda bi, pi, t: (bi, 0)),          # qpos
        pl.BlockSpec((1, n_q, h, d), lambda bi, pi, t: (bi, 0, 0, 0)),
        page_spec,                                                  # k page
        page_spec,                                                  # v page
    ]
    operands = [qpos, q4, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, page_len, h), lambda bi, pi, t: (t[bi, pi], 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, n_tables),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_q, h, d),
                               lambda bi, pi, t: (bi, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, n_q, 1), jnp.float32),   # m
            pltpu.VMEM((h, n_q, 1), jnp.float32),   # l
            pltpu.VMEM((h, n_q, d), jnp.float32),   # acc
        ],
    )
    kernel = functools.partial(
        _paged_kernel, page_len=page_len, n_tables=n_tables,
        quantized=quantized, scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_q, h, d), q4.dtype),
        interpret=interpret,
    )(tables, *operands)


def _check_impl(impl: str) -> None:
    if impl not in ("gather", "kernel"):
        raise ValueError(
            f"unknown paged attention impl {impl!r} (gather|kernel; resolve "
            "'auto' via autodist_tpu.ops.crossover.resolve_paged_impl first)")


# ------------------------------------------------------------- entry points
def paged_decode_attention(q, k_pages, v_pages, page_tables, positions, *,
                           k_scale=None, v_scale=None, impl: str = "gather",
                           compute_dtype: Any = None,
                           interpret: Optional[bool] = None):
    """Decode-step attention: ``q [B, H, D]`` (one query per row),
    ``page_tables [B, P]``, ``positions [B]``. Returns ``[B, H, D]``.

    ``impl='gather'`` is the verbatim pre-kernel program (einsum spellings
    preserved so pre-existing streams stay bit-identical); ``'kernel'``
    streams pages through the pallas block loop.
    """
    _check_impl(impl)
    compute_dtype = compute_dtype or q.dtype
    if impl == "kernel":
        out = _kernel_attention(q[:, None], k_pages, v_pages, page_tables,
                                positions[:, None], k_scale, v_scale,
                                interpret)
        return out[:, 0]
    head_dim = q.shape[-1]
    timeline = page_tables.shape[1] * k_pages.shape[1]
    ck = _gather_timeline(k_pages, k_scale, page_tables, compute_dtype)
    cv = _gather_timeline(v_pages, v_scale, page_tables, compute_dtype)
    mask = position_mask(timeline, positions)                     # [B, T]
    logits = jnp.einsum("bhd,bthd->bht", q, ck).astype(jnp.float32)
    logits = logits / jnp.sqrt(head_dim).astype(jnp.float32)
    logits = apply_mask(logits, mask[:, None, :])
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bht,bthd->bhd", probs, cv)


def paged_prefill_attention(q, k_pages, v_pages, page_table, positions, *,
                            k_scale=None, v_scale=None, impl: str = "gather",
                            compute_dtype: Any = None,
                            interpret: Optional[bool] = None):
    """Prefill-chunk attention: ``q [C, H, D]`` (one row's chunk),
    ``page_table [P]``, ``positions [C]`` absolute. Returns ``[C, H, D]``."""
    _check_impl(impl)
    compute_dtype = compute_dtype or q.dtype
    if impl == "kernel":
        out = _kernel_attention(q[None], k_pages, v_pages, page_table[None],
                                positions[None], k_scale, v_scale, interpret)
        return out[0]
    head_dim = q.shape[-1]
    timeline = page_table.shape[0] * k_pages.shape[1]
    ck = _gather_timeline(k_pages, k_scale, page_table, compute_dtype)
    cv = _gather_timeline(v_pages, v_scale, page_table, compute_dtype)
    mask = position_mask(timeline, positions)                     # [C, T]
    logits = jnp.einsum("chd,thd->hct", q, ck).astype(jnp.float32)
    logits = logits / jnp.sqrt(head_dim).astype(jnp.float32)
    logits = apply_mask(logits, mask[None, :, :])
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("hct,thd->chd", probs, cv)


def paged_verify_attention(q, k_pages, v_pages, page_tables, rows_pos, *,
                           k_scale=None, v_scale=None, impl: str = "gather",
                           compute_dtype: Any = None,
                           interpret: Optional[bool] = None):
    """Spec-verify attention: ``q [B, K1, H, D]`` (pending token + K drafts
    per row), ``page_tables [B, P]``, ``rows_pos [B, K1]`` absolute query
    positions. Returns ``[B, K1, H, D]``."""
    _check_impl(impl)
    compute_dtype = compute_dtype or q.dtype
    if impl == "kernel":
        return _kernel_attention(q, k_pages, v_pages, page_tables, rows_pos,
                                 k_scale, v_scale, interpret)
    head_dim = q.shape[-1]
    timeline = page_tables.shape[1] * k_pages.shape[1]
    ck = _gather_timeline(k_pages, k_scale, page_tables, compute_dtype)
    cv = _gather_timeline(v_pages, v_scale, page_tables, compute_dtype)
    mask = position_mask(timeline, rows_pos)                      # [B, K1, T]
    logits = jnp.einsum("bqhd,bthd->bhqt", q, ck).astype(jnp.float32)
    logits = logits / jnp.sqrt(head_dim).astype(jnp.float32)
    logits = apply_mask(logits, mask[:, None, :, :])
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqt,bthd->bqhd", probs, cv)
