"""TPU kernels (pallas) for the hot ops.

The reference has no kernels of its own — its compute muscle is stock TF
C++/CUDA (SURVEY.md §2: "zero C++/Rust/CUDA files"). The TPU-native build owns
its hot ops instead: pallas kernels tuned for MXU/VMEM, with jnp reference
implementations used for CPU fallback and numerics tests.
"""
from autodist_tpu.ops.flash_attention import flash_attention, mha_reference

__all__ = ["flash_attention", "mha_reference"]
